#!/usr/bin/env python3
"""Release tooling (parity with py/kubeflow/tf_operator/release.py:122-462 —
build artifacts, build/push the operator image, changelog), local-first:

  python tools/release.py build      native lib + versioned source tarball
  python tools/release.py test       the release gate (pytest -x)
  python tools/release.py image      docker build (uses build/Dockerfile);
                                     prints the command if docker is absent
  python tools/release.py changelog  commits since the last release tag
  python tools/release.py publish    push the image to a registry and tag the
                                     green postsubmit (parity with reference
                                     release.py:248 build_and_push_artifacts
                                     + prow.py tag-green): DRY-RUN by default,
                                     pass --execute to actually push. Requires
                                     a green CI summary (tools/ci.py) unless
                                     --no-gate.

Artifacts land in dist/: tf_operator_tpu-<version>+<sha>.tar.gz (git archive,
reproducible) and libtpujob_native.so.
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIST = os.path.join(REPO, "dist")


def sh(cmd: list[str], **kw) -> subprocess.CompletedProcess:
    print("+", " ".join(cmd), file=sys.stderr)
    return subprocess.run(cmd, cwd=REPO, check=True, **kw)


def _version_tag() -> str:
    sys.path.insert(0, REPO)
    from tf_operator_tpu.version import git_sha, version_info

    return f"{version_info()['version']}+{git_sha()}"


def cmd_build(args) -> int:
    os.makedirs(DIST, exist_ok=True)
    tag = _version_tag()
    # 1. Native library.
    sh(["make", "-C", os.path.join(REPO, "native")])
    shutil.copy2(
        os.path.join(REPO, "native", "build", "libtpujob_native.so"),
        os.path.join(DIST, "libtpujob_native.so"),
    )
    # 2. Reproducible source tarball of the committed tree.
    tarball = os.path.join(DIST, f"tf_operator_tpu-{tag}.tar.gz")
    sh(["git", "archive", "--format=tar.gz",
        f"--prefix=tf_operator_tpu-{tag}/", "-o", tarball, "HEAD"])
    print(f"built: {tarball}")
    print(f"built: {DIST}/libtpujob_native.so")
    return 0


def cmd_test(args) -> int:
    return subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-x", "-q"], cwd=REPO
    ).returncode


def cmd_image(args) -> int:
    tag = args.tag or f"tpujob-operator:{_version_tag()}"
    cmd = ["docker", "build", "-f", "build/Dockerfile", "-t", tag, "."]
    if shutil.which("docker") is None:
        print("docker not available here; on a build host run:")
        print("  " + " ".join(cmd))
        return 0
    sh(cmd)
    if args.push:
        sh(["docker", "push", tag])
    return 0


def cmd_publish(args) -> int:
    """Push image + git tag for a green build. Dry-run unless --execute."""
    import json

    tag = _version_tag()
    # Gate on CI: the reference only tags postsubmits whose Prow run was
    # green; our equivalent evidence is tools/ci.py's summary.json.
    if not args.no_gate:
        summary_path = args.ci_summary or os.path.join(
            REPO, "artifacts", "ci", "summary.json"
        )
        if not os.path.exists(summary_path):
            print(f"publish: no CI summary at {summary_path}; run "
                  f"`python tools/ci.py` first or pass --no-gate",
                  file=sys.stderr)
            return 1
        with open(summary_path) as f:
            summary = json.load(f)
        if not summary.get("ok"):
            bad = [n for n, r in summary.get("stages", {}).items()
                   if r.get("status") != "ok"]
            print(f"publish: CI not green (stages {bad}); refusing to "
                  f"publish", file=sys.stderr)
            return 1
        if summary.get("skipped_stages") or summary.get("partial"):
            how = (f"skipped stages {summary.get('skipped_stages')}"
                   if summary.get("skipped_stages") else "a --only run")
            print(f"publish: CI summary records {how}; a partial run cannot "
                  f"green-light a release (use --no-gate to override)",
                  file=sys.stderr)
            return 1
        default_pipeline = os.path.join(REPO, "ci", "pipeline.yaml")
        if (summary.get("pipeline")
                and os.path.abspath(summary["pipeline"])
                != os.path.abspath(default_pipeline)):
            print(f"publish: CI summary is from pipeline "
                  f"{summary['pipeline']}, not {default_pipeline}; refusing",
                  file=sys.stderr)
            return 1
        head = subprocess.run(
            ["git", "-C", REPO, "rev-parse", "HEAD"],
            capture_output=True, text=True,
        ).stdout.strip()
        if summary.get("git_sha") and head and summary["git_sha"] != head:
            print(f"publish: CI summary is for {summary['git_sha'][:12]} but "
                  f"HEAD is {head[:12]}; re-run tools/ci.py on this commit",
                  file=sys.stderr)
            return 1
        print(f"publish: CI green ({summary_path})", file=sys.stderr)

    image = f"{args.registry.rstrip('/')}/tpujob-operator:{tag}"
    git_tag = f"green-postsubmit-{tag.replace('+', '-')}"
    plan = [
        ["docker", "build", "-f", "build/Dockerfile", "-t", image, "."],
        ["docker", "push", image],
        ["git", "tag", "-f", git_tag, "HEAD"],
        ["git", "push", args.remote, git_tag],
    ]
    if not args.execute:
        print(f"publish (dry-run): image={image} tag={git_tag}")
        for cmd in plan:
            print("  would run:", " ".join(cmd))
        print("pass --execute to run the above")
        return 0
    if shutil.which("docker") is None:
        # Tagging green without a pushed image would advertise a release
        # nobody can pull; abort before any git step.
        print("publish: docker unavailable on this host — cannot push the "
              "image, so the green tag will not be created. Run on a build "
              "host:", file=sys.stderr)
        for cmd in plan:
            print("  " + " ".join(cmd), file=sys.stderr)
        return 1
    for cmd in plan:
        sh(cmd)
    print(f"published: {image} (+git tag {git_tag})")
    return 0


def cmd_changelog(args) -> int:
    r = subprocess.run(
        ["git", "-C", REPO, "describe", "--tags", "--abbrev=0"],
        capture_output=True, text=True,
    )
    since = r.stdout.strip() if r.returncode == 0 else None
    rev = f"{since}..HEAD" if since else "HEAD"
    sh(["git", "log", "--oneline", "--no-decorate", rev])
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="release.py")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("build").set_defaults(fn=cmd_build)
    sub.add_parser("test").set_defaults(fn=cmd_test)
    p = sub.add_parser("image")
    p.add_argument("--tag", default=None)
    p.add_argument("--push", action="store_true")
    p.set_defaults(fn=cmd_image)
    p = sub.add_parser("publish")
    p.add_argument("--registry", required=True,
                   help="image registry prefix, e.g. gcr.io/my-project")
    p.add_argument("--remote", default="origin", help="git remote for tags")
    p.add_argument("--ci-summary", default=None,
                   help="path to tools/ci.py summary.json (default "
                        "artifacts/ci/summary.json)")
    p.add_argument("--no-gate", action="store_true",
                   help="skip the green-CI check")
    p.add_argument("--execute", action="store_true",
                   help="actually push; default is a dry-run that prints "
                        "the plan")
    p.set_defaults(fn=cmd_publish)
    sub.add_parser("changelog").set_defaults(fn=cmd_changelog)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
