#!/usr/bin/env python3
"""Release tooling (parity with py/kubeflow/tf_operator/release.py:122-462 —
build artifacts, build/push the operator image, changelog), local-first:

  python tools/release.py build      native lib + versioned source tarball
  python tools/release.py test       the release gate (pytest -x)
  python tools/release.py image      docker build (uses build/Dockerfile);
                                     prints the command if docker is absent
  python tools/release.py changelog  commits since the last release tag

Artifacts land in dist/: tf_operator_tpu-<version>+<sha>.tar.gz (git archive,
reproducible) and libtpujob_native.so.
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIST = os.path.join(REPO, "dist")


def sh(cmd: list[str], **kw) -> subprocess.CompletedProcess:
    print("+", " ".join(cmd), file=sys.stderr)
    return subprocess.run(cmd, cwd=REPO, check=True, **kw)


def _version_tag() -> str:
    sys.path.insert(0, REPO)
    from tf_operator_tpu.version import git_sha, version_info

    return f"{version_info()['version']}+{git_sha()}"


def cmd_build(args) -> int:
    os.makedirs(DIST, exist_ok=True)
    tag = _version_tag()
    # 1. Native library.
    sh(["make", "-C", os.path.join(REPO, "native")])
    shutil.copy2(
        os.path.join(REPO, "native", "build", "libtpujob_native.so"),
        os.path.join(DIST, "libtpujob_native.so"),
    )
    # 2. Reproducible source tarball of the committed tree.
    tarball = os.path.join(DIST, f"tf_operator_tpu-{tag}.tar.gz")
    sh(["git", "archive", "--format=tar.gz",
        f"--prefix=tf_operator_tpu-{tag}/", "-o", tarball, "HEAD"])
    print(f"built: {tarball}")
    print(f"built: {DIST}/libtpujob_native.so")
    return 0


def cmd_test(args) -> int:
    return subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-x", "-q"], cwd=REPO
    ).returncode


def cmd_image(args) -> int:
    tag = args.tag or f"tpujob-operator:{_version_tag()}"
    cmd = ["docker", "build", "-f", "build/Dockerfile", "-t", tag, "."]
    if shutil.which("docker") is None:
        print("docker not available here; on a build host run:")
        print("  " + " ".join(cmd))
        return 0
    sh(cmd)
    if args.push:
        sh(["docker", "push", tag])
    return 0


def cmd_changelog(args) -> int:
    r = subprocess.run(
        ["git", "-C", REPO, "describe", "--tags", "--abbrev=0"],
        capture_output=True, text=True,
    )
    since = r.stdout.strip() if r.returncode == 0 else None
    rev = f"{since}..HEAD" if since else "HEAD"
    sh(["git", "log", "--oneline", "--no-decorate", rev])
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="release.py")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("build").set_defaults(fn=cmd_build)
    sub.add_parser("test").set_defaults(fn=cmd_test)
    p = sub.add_parser("image")
    p.add_argument("--tag", default=None)
    p.add_argument("--push", action="store_true")
    p.set_defaults(fn=cmd_image)
    sub.add_parser("changelog").set_defaults(fn=cmd_changelog)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
