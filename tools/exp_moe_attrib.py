"""Attribute the sparse-MoE roofline's "Unknown" bucket op by op (VERDICT r4 #1).

BENCH_r04's moe_roofline says 20.1% of step self-time is bound_by=Unknown —
ops xprof's hlo_stats could not classify against either roofline. This tool
runs the exact bench moe-lm sparse config under an XProf trace and prints the
FULL per-op accounting the bench's 5-op summary truncates:

  - self-time share grouped by (bound_by, HLO category)
  - every op >= 0.3% in the Unknown bucket, with name + category
  - a routing-chain rollup: sort / scatter / gather / ragged-dot / fusion
    shares matched by op-name substring, so the argsort+bincount+permute
    suspect chain (models/moe.py:249-272) gets a measured number

Runs in a subprocess (one process per chip). Usage:
  python tools/exp_moe_attrib.py [--steps 10] [--out artifacts/moe_attrib.json]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import json, os, sys, tempfile, time
import jax, jax.numpy as jnp, optax

sys.path.insert(0, {repo!r})
from tf_operator_tpu.models import moe as moe_lib
from tf_operator_tpu.parallel import mesh as mesh_lib
from tf_operator_tpu.parallel import sharding_rules
from tf_operator_tpu.parallel.ring_attention import make_attention_fn
from tf_operator_tpu.parallel.train_step import (
    create_train_state, make_scanned_train_step, shard_state,
)

steps = {steps}
seq, batch = 2048, 8
cfg = moe_lib.MoEConfig(
    vocab_size=32000, num_layers=12, hidden=768, num_heads=6,
    max_len=seq, num_experts=8, top_k=2, moe_every=2, dispatch="sparse",
)
mesh = mesh_lib.make_mesh({{"dp": 1}})
model = moe_lib.MoETransformerLM(cfg, attn_fn=make_attention_fn(mesh, causal=True))
params = model.init(jax.random.key(0), jnp.zeros((1, seq), jnp.int32))["params"]

def loss_fn(params, model_state, batch, rng):
    return moe_lib.moe_lm_loss(model, params, batch["tokens"]), model_state

def make_batch(rng):
    return {{"tokens": jax.random.randint(rng, (batch, seq), 0,
                                          cfg.vocab_size)}}

tx = optax.adamw(1e-3)
state = shard_state(create_train_state(params, tx), mesh,
                    sharding_rules.MOE_RULES)
opts = {{"xla_tpu_scoped_vmem_limit_kib": "49152"}}
compile_scanned = make_scanned_train_step(
    loss_fn, tx, mesh, make_batch, rules=sharding_rules.MOE_RULES,
    compiler_options=opts,
)
chunk = max(1, min(5, steps // 2))
step_chunk = compile_scanned(state, chunk)
state, m = step_chunk(state)
float(m["loss"])  # warm-up + host sync

trace_dir = {trace_dir!r}
with jax.profiler.trace(trace_dir):
    for _ in range(max(1, steps // chunk)):
        state, m = step_chunk(state)
    float(m["loss"])
print(json.dumps({{"ok": True, "trace_dir": trace_dir}}))
"""


def full_attribution(trace_dir: str) -> dict | None:
    """Per-op accounting: bound_by x category shares + Unknown op list."""
    import glob

    sys.path.insert(0, REPO)
    from tf_operator_tpu.utils.roofline import _load_hlo_stats

    paths = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                             recursive=True))
    rows = _load_hlo_stats(paths) if paths else None
    if not rows:
        return None
    t_key = "Total self time (us)"
    total = sum(r.get(t_key) or 0 for r in rows)
    if total <= 0:
        return None

    by_bound_cat: dict[str, float] = {}
    unknown_ops = []
    chain = {"sort": 0.0, "scatter": 0.0, "gather": 0.0, "ragged": 0.0,
             "top-k": 0.0, "bincount/reduce": 0.0, "other": 0.0}
    for r in rows:
        t = r.get(t_key) or 0
        b = str(r.get("Bound by") or "Unknown")
        cat = str(r.get("HLO op category") or "?")
        name = str(r.get("HLO op name") or "?")
        by_bound_cat[f"{b} / {cat}"] = by_bound_cat.get(f"{b} / {cat}", 0) + t
        if b == "Unknown":
            unknown_ops.append((t, name, cat))
            lname = (name + " " + cat).lower()
            if "sort" in lname:
                chain["sort"] += t
            elif "scatter" in lname:
                chain["scatter"] += t
            elif "gather" in lname or "take" in lname:
                chain["gather"] += t
            elif "ragged" in lname:
                chain["ragged"] += t
            elif "top-k" in lname or "topk" in lname:
                chain["top-k"] += t
            elif "reduce" in lname or "bincount" in lname:
                chain["bincount/reduce"] += t
            else:
                chain["other"] += t

    unknown_ops.sort(key=lambda x: -x[0])
    pct = lambda t: round(t / total * 100, 2)  # noqa: E731
    return {
        "total_self_time_us": round(total, 1),
        "bound_by_x_category_pct": {
            k: pct(v) for k, v in
            sorted(by_bound_cat.items(), key=lambda kv: -kv[1])
            if v / total >= 0.002
        },
        "unknown_pct_total": pct(sum(t for t, _, _ in unknown_ops)),
        "unknown_chain_rollup_pct": {k: pct(v) for k, v in
                                     sorted(chain.items(),
                                            key=lambda kv: -kv[1]) if v},
        "unknown_ops": [
            {"name": n, "category": c, "pct": pct(t)}
            for t, n, c in unknown_ops if t / total >= 0.003
        ],
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--out", default="artifacts/moe_attrib.json")
    args = ap.parse_args()

    import tempfile

    trace_dir = tempfile.mkdtemp(prefix="tpujob-moe-attrib-")
    r = subprocess.run(
        [sys.executable, "-c",
         CHILD.format(repo=REPO, steps=args.steps, trace_dir=trace_dir)],
        capture_output=True, text=True, timeout=1800,
    )
    if r.returncode != 0:
        print(json.dumps({"error": r.stderr.strip().splitlines()[-3:]}))
        return 1
    attrib = full_attribution(trace_dir)
    out = {"config": "moe-lm 12Lx768h E8 top2 seq2048 b8 sparse",
           "steps": args.steps, "attribution": attrib}
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))
    import shutil

    shutil.rmtree(trace_dir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
