# Package marker so `python -m tools.analysis` works from the repo root.
# The scripts in this directory are still runnable directly
# (`python tools/ci.py`, `python tools/lint.py`, ...): running a file as a
# script does not involve the package.
