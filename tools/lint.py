"""Static analysis for the repo — the CI py-lint stage.

The reference gates CI on pylint (py/kubeflow/tf_operator/py_checks.py:1-60);
this environment ships no linter and installs are off-limits, so the stage is
implemented here on stdlib `ast`. Checks (each with a stable code):

  F821 undefined-name        Name loads that no enclosing scope or builtin
                             defines — catches typos, stale refactors.
  F401 unused-import         Imported name never read in the module.
  F811 redefinition          def/class redefined in the same scope without use.
  B006 mutable-default       def f(x=[]) / {} / set() defaults.
  F541 f-string-no-placeholder  f"" with no {} — usually a forgotten format.
  E722 bare-except           `except:` catches SystemExit/KeyboardInterrupt.

Suppression: `# noqa` (whole line) or `# noqa: F821,...` (specific codes).
Exit code 1 if any finding survives. Usage:

  python tools/lint.py [paths...]     # default: the package + tools + tests
"""

from __future__ import annotations

import ast
import builtins
import sys
from pathlib import Path

BUILTINS = set(dir(builtins)) | {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__class__", "__path__",
}

# Default lint roots, resolved against the repo (not the cwd) so the CI
# stage and tests behave identically from any directory.
_REPO = Path(__file__).resolve().parent.parent
DEFAULT_PATHS = [str(_REPO / p) for p in (
    "tf_operator_tpu", "tools", "tests", "bench.py", "__graft_entry__.py")]


class Scope:
    __slots__ = ("node", "names", "globals", "nonlocals", "is_class")

    def __init__(self, node, is_class=False):
        self.node = node
        self.names: set[str] = set()
        self.globals: set[str] = set()
        self.nonlocals: set[str] = set()
        self.is_class = is_class


def _target_names(t) -> list[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out = []
        for e in t.elts:
            out.extend(_target_names(e))
        return out
    if isinstance(t, ast.Starred):
        return _target_names(t.value)
    return []


class _Binder(ast.NodeVisitor):
    """First pass over one scope body: collect every name it binds."""

    def __init__(self, scope: Scope):
        self.s = scope

    # do not descend into nested scopes — they bind their own names
    def visit_FunctionDef(self, n):
        self.s.names.add(n.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, n):
        self.s.names.add(n.name)

    def visit_Lambda(self, n):
        pass

    def _comp(self, n):
        pass  # comprehensions are their own scope (py3)

    visit_ListComp = visit_SetComp = visit_DictComp = visit_GeneratorExp = _comp

    def visit_Import(self, n):
        for a in n.names:
            self.s.names.add((a.asname or a.name).split(".")[0])

    def visit_ImportFrom(self, n):
        for a in n.names:
            if a.name == "*":
                self.s.names.add("*")
            else:
                self.s.names.add(a.asname or a.name)

    def visit_Assign(self, n):
        for t in n.targets:
            self.s.names.update(_target_names(t))
        self.generic_visit(n)

    def visit_AnnAssign(self, n):
        self.s.names.update(_target_names(n.target))
        self.generic_visit(n)

    def visit_AugAssign(self, n):
        self.s.names.update(_target_names(n.target))
        self.generic_visit(n)

    def visit_NamedExpr(self, n):  # walrus binds in the containing scope
        self.s.names.update(_target_names(n.target))
        self.generic_visit(n)

    def visit_For(self, n):
        self.s.names.update(_target_names(n.target))
        self.generic_visit(n)

    visit_AsyncFor = visit_For

    def visit_While(self, n):
        self.generic_visit(n)

    def visit_With(self, n):
        for item in n.items:
            if item.optional_vars is not None:
                self.s.names.update(_target_names(item.optional_vars))
        self.generic_visit(n)

    visit_AsyncWith = visit_With

    def visit_ExceptHandler(self, n):
        if n.name:
            self.s.names.add(n.name)
        self.generic_visit(n)

    def visit_Global(self, n):
        self.s.globals.update(n.names)

    def visit_Nonlocal(self, n):
        self.s.nonlocals.update(n.names)

    def visit_MatchAs(self, n):
        if n.name:
            self.s.names.add(n.name)
        self.generic_visit(n)

    def visit_MatchStar(self, n):
        if n.name:
            self.s.names.add(n.name)
        self.generic_visit(n)

    def visit_MatchMapping(self, n):
        if n.rest:
            self.s.names.add(n.rest)
        self.generic_visit(n)


def _bind_args(scope: Scope, args: ast.arguments):
    for a in (list(args.posonlyargs) + list(args.args)
              + list(args.kwonlyargs)):
        scope.names.add(a.arg)
    if args.vararg:
        scope.names.add(args.vararg.arg)
    if args.kwarg:
        scope.names.add(args.kwarg.arg)


class Linter(ast.NodeVisitor):
    def __init__(self, path: str, src: str, tree: ast.Module):
        self.path = path
        self.lines = src.splitlines()
        self.findings: list[tuple[int, str, str]] = []
        self.scopes: list[Scope] = []
        mod_scope = Scope(tree)
        _Binder(mod_scope).generic_visit(tree)
        # `global x` + assignment inside any function binds x at module
        # scope — collect from the WHOLE tree (the binder stops at nested
        # scopes by design).
        for node in ast.walk(tree):
            if isinstance(node, ast.Global):
                mod_scope.names.update(node.names)
        self.scopes.append(mod_scope)
        self.has_star = "*" in mod_scope.names
        # import tracking: name -> (lineno, stmt) for F401
        self.imports: dict[str, int] = {}
        self.used: set[str] = set()
        # textual fallback: names in docstrings/comments don't count, but a
        # name used only inside a nested string-annotation should — keep it
        # simple: __all__ re-exports and package __init__ are exempt below.
        self.is_init = path.endswith("__init__.py")

    def report(self, node, code: str, msg: str):
        line_no = node if isinstance(node, int) else getattr(node, "lineno", 1)
        line = self.lines[line_no - 1] if line_no <= len(self.lines) else ""
        if "# noqa" in line:
            tail = line.split("# noqa", 1)[1].strip()
            if not tail.startswith(":") or code in tail[1:].replace(" ", "").split(","):
                return
        self.findings.append((line_no, code, msg))

    # ---- scope machinery ----
    def _enter(self, node, is_class=False, args: ast.arguments | None = None):
        s = Scope(node, is_class=is_class)
        if args is not None:
            _bind_args(s, args)
        _Binder(s).generic_visit(node)
        self.scopes.append(s)
        return s

    def _exit(self):
        self.scopes.pop()

    def _defined(self, name: str) -> bool:
        if self.has_star or name in BUILTINS:
            return True
        top = self.scopes[-1]
        if name in top.globals:
            return name in self.scopes[0].names
        # class scopes are skipped for nested lookups; the directly
        # innermost scope always sees its own names
        for i, s in enumerate(reversed(self.scopes)):
            if i > 0 and s.is_class:
                continue
            if name in s.names:
                return True
        return False

    # ---- visitors ----
    def visit_Name(self, n):
        if isinstance(n.ctx, ast.Load):
            self.used.add(n.id)
            if not self._defined(n.id):
                self.report(n, "F821", f"undefined name '{n.id}'")
        self.generic_visit(n)

    def visit_Attribute(self, n):
        self.generic_visit(n)

    def _check_redefinition(self, body: list):
        """F811: same-scope def/class redefined with no decorators on
        either (decorators — @overload, @prop.setter — legitimately reuse
        the name)."""
        seen: dict[str, ast.AST] = {}
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                prev = seen.get(stmt.name)
                if (prev is not None and not stmt.decorator_list
                        and not prev.decorator_list):
                    self.report(stmt, "F811",
                                f"redefinition of '{stmt.name}' from line "
                                f"{prev.lineno}")
                seen[stmt.name] = stmt

    def _function(self, n):
        for d in n.decorator_list:
            self.visit(d)
        for default in list(n.args.defaults) + [
                d for d in n.args.kw_defaults if d is not None]:
            self.visit(default)
            if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(default, ast.Call)
                    and isinstance(default.func, ast.Name)
                    and default.func.id in ("list", "dict", "set")):
                self.report(default, "B006",
                            f"mutable default argument in '{n.name}'")
        if n.returns is not None:
            self.visit(n.returns)
        for a in (list(n.args.posonlyargs) + list(n.args.args)
                  + list(n.args.kwonlyargs)):
            if a.annotation is not None:
                self.visit(a.annotation)
        self._enter(n, args=n.args)
        self._check_redefinition(n.body)
        for stmt in n.body:
            self.visit(stmt)
        self._exit()

    visit_FunctionDef = visit_AsyncFunctionDef = _function

    def visit_Lambda(self, n):
        for default in list(n.args.defaults) + [
                d for d in n.args.kw_defaults if d is not None]:
            self.visit(default)
        self._enter(n, args=n.args)
        self.visit(n.body)
        self._exit()

    def visit_ClassDef(self, n):
        for d in n.decorator_list:
            self.visit(d)
        for b in n.bases:
            self.visit(b)
        for k in n.keywords:
            self.visit(k.value)
        self._enter(n, is_class=True)
        self._check_redefinition(n.body)
        for stmt in n.body:
            self.visit(stmt)
        self._exit()

    def _comp(self, n):
        # evaluate first iterable in the enclosing scope, rest inside
        s = Scope(n)
        for gen in n.generators:
            s.names.update(_target_names(gen.target))
        self.visit(n.generators[0].iter)
        self.scopes.append(s)
        for i, gen in enumerate(n.generators):
            if i > 0:
                self.visit(gen.iter)
            for cond in gen.ifs:
                self.visit(cond)
        if isinstance(n, ast.DictComp):
            self.visit(n.key)
            self.visit(n.value)
        else:
            self.visit(n.elt)
        self._exit()

    visit_ListComp = visit_SetComp = visit_DictComp = visit_GeneratorExp = _comp

    def visit_Import(self, n):
        for a in n.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports.setdefault(name, n.lineno)
        self.generic_visit(n)

    def visit_ImportFrom(self, n):
        if n.module == "__future__":  # compiler directive, not a binding
            return
        for a in n.names:
            if a.name != "*":
                self.imports.setdefault(a.asname or a.name, n.lineno)
        self.generic_visit(n)

    def visit_JoinedStr(self, n):
        if not any(isinstance(v, ast.FormattedValue) for v in n.values):
            self.report(n, "F541", "f-string without placeholders")
        self._visit_joined_values(n)

    def _visit_joined_values(self, n: ast.JoinedStr):
        """Recurse into placeholder VALUES — including those nested inside
        format specs (f"{x:{width}}") — without re-running the F541 check:
        a format spec is itself a placeholder-less JoinedStr."""
        for v in n.values:
            if isinstance(v, ast.FormattedValue):
                self.visit(v.value)
                if isinstance(v.format_spec, ast.JoinedStr):
                    self._visit_joined_values(v.format_spec)

    def visit_ExceptHandler(self, n):
        if n.type is None:
            self.report(n, "E722", "bare 'except:'")
        self.generic_visit(n)

    def finish(self, tree: ast.Module):
        # F401: module-level imports never read anywhere in the file.
        # __init__.py re-exports and explicit __all__ entries are exempt.
        if self.is_init:
            return
        exported = set()
        for stmt in tree.body:
            if (isinstance(stmt, ast.Assign) and stmt.targets
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "__all__"
                    and isinstance(stmt.value, (ast.List, ast.Tuple))):
                exported = {e.value for e in stmt.value.elts
                            if isinstance(e, ast.Constant)}
        for name, lineno in self.imports.items():
            if name not in self.used and name not in exported:
                self.report(lineno, "F401", f"'{name}' imported but unused")


def lint_file(path: Path) -> list[str]:
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: E999 syntax error: {e.msg}"]
    linter = Linter(str(path), src, tree)
    linter._check_redefinition(tree.body)
    for stmt in tree.body:
        linter.visit(stmt)
    linter.finish(tree)
    return [f"{path}:{line}: {code} {msg}"
            for line, code, msg in sorted(linter.findings)]


def main(argv: list[str]) -> int:
    roots = [Path(p) for p in (argv or DEFAULT_PATHS)]
    files: list[Path] = []
    for r in roots:
        if r.is_dir():
            files.extend(sorted(r.rglob("*.py")))
        elif r.suffix == ".py":
            files.append(r)
    findings = []
    for f in files:
        if "__pycache__" in f.parts:
            continue
        findings.extend(lint_file(f))
    for line in findings:
        print(line)
    print(f"lint: {len(files)} files, {len(findings)} findings",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
