"""Device-placement smoke test — the reference's tf_smoke.py, TPU-native.

The reference (examples/tf_sample/tf_smoke.py) ran an explicit matmul on every
device to prove placement and cross-device reduction worked. Same idea here:
enumerate JAX devices, run a bf16 matmul pinned to each, then an all-device
psum over a mesh, and report timings.

Run standalone or inside a TrainJob replica:
    python examples/smoke.py [--size 4096]
"""

from __future__ import annotations

import argparse
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=2048)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    devices = jax.devices()
    print(f"backend={jax.default_backend()} devices={len(devices)}")
    for d in devices:
        print(f"  {d.id}: {d.device_kind} ({d.platform})")

    n = args.size
    key = jax.random.key(0)
    ok = True

    # Per-device matmul (the reference's per-GPU a@b check).
    for d in devices:
        a = jax.device_put(jax.random.normal(key, (n, n), jnp.bfloat16), d)
        b = jax.device_put(jax.random.normal(key, (n, n), jnp.bfloat16), d)
        f = jax.jit(jnp.matmul, device=d)
        f(a, b).block_until_ready()  # compile
        t0 = time.perf_counter()
        c = f(a, b).block_until_ready()
        dt = time.perf_counter() - t0
        tflops = 2 * n**3 / dt / 1e12
        finite = bool(jnp.isfinite(c.astype(jnp.float32)).all())
        ok = ok and finite
        print(f"  device {d.id}: {n}x{n} bf16 matmul {dt*1e3:.2f} ms "
              f"({tflops:.1f} TFLOP/s) finite={finite}")

    # Cross-device reduction (the reference's cross-GPU sum).
    if len(devices) > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(devices, ("dp",))
        x = jax.device_put(
            jnp.ones((len(devices), 16)), NamedSharding(mesh, P("dp"))
        )
        total = jax.jit(lambda v: v.sum())(x)
        expect = float(len(devices) * 16)
        print(f"  all-device reduce: {float(total)} (expect {expect})")
        ok = ok and float(total) == expect

    print("SMOKE PASSED" if ok else "SMOKE FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
