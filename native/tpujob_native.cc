// tpujob native runtime: the operator's hot-loop primitives and the local
// executor's process supervisor, in C++.
//
// The reference's native tier is the operator binary itself (Go —
// SURVEY.md §0: pkg/common/jobcontroller workqueue/expectations hot loop,
// and kubelet doing process supervision below it). This library is the
// TPU build's equivalent: the per-reconcile data structures the controller
// hammers (client-go-style rate-limited workqueue, expectations cache,
// exit-code policy — ref jobcontroller.go:110-133, train_util.go:18-55)
// and a kubelet-stand-in process supervisor (setsid process groups,
// pidfd-based waits, whole-tree kills) behind a plain C ABI consumed from
// Python via ctypes (tf_operator_tpu/native). Pure-Python fallbacks with
// identical semantics live in core/workqueue.py, core/expectations.py,
// utils/exit_codes.py and runtime/local.py.
//
// Build: make -C native   ->  native/build/libtpujob_native.so

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <queue>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <signal.h>
#include <stdint.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

extern char** environ;

namespace {

using Clock = std::chrono::steady_clock;

double now_s() {
  return std::chrono::duration<double>(Clock::now().time_since_epoch()).count();
}

// ---------------------------------------------------------------------------
// Rate limiters (client-go DefaultControllerRateLimiter shape)
// ---------------------------------------------------------------------------

class ItemExponentialRateLimiter {
 public:
  ItemExponentialRateLimiter(double base_delay, double max_delay)
      : base_(base_delay), max_(max_delay) {}

  double when(const std::string& item) {
    std::lock_guard<std::mutex> g(mu_);
    int n = 0;
    auto it = failures_.find(item);
    if (it != failures_.end()) n = it->second;
    failures_[item] = n + 1;
    // base * 2^n, saturating.
    double d = base_;
    for (int i = 0; i < n && d < max_; i++) d *= 2.0;
    return std::min(d, max_);
  }

  void forget(const std::string& item) {
    std::lock_guard<std::mutex> g(mu_);
    failures_.erase(item);
  }

  int num_requeues(const std::string& item) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = failures_.find(item);
    return it == failures_.end() ? 0 : it->second;
  }

 private:
  double base_, max_;
  std::mutex mu_;
  std::unordered_map<std::string, int> failures_;
};

class BucketRateLimiter {
 public:
  BucketRateLimiter(double qps, int burst)
      : qps_(qps), burst_(burst), tokens_(burst), last_(now_s()) {}

  double when() {
    std::lock_guard<std::mutex> g(mu_);
    double now = now_s();
    tokens_ = std::min(static_cast<double>(burst_), tokens_ + (now - last_) * qps_);
    last_ = now;
    if (tokens_ >= 1.0) {
      tokens_ -= 1.0;
      return 0.0;
    }
    double need = 1.0 - tokens_;
    tokens_ -= 1.0;
    return need / qps_;
  }

 private:
  double qps_;
  int burst_;
  double tokens_, last_;
  std::mutex mu_;
};

// ---------------------------------------------------------------------------
// Rate-limited deduplicating workqueue (client-go workqueue.Type +
// DelayingQueue + RateLimitingQueue semantics; see core/workqueue.py).
// ---------------------------------------------------------------------------

class WorkQueue {
 public:
  WorkQueue(double qps, int burst, double base_delay, double max_delay)
      : item_rl_(base_delay, max_delay), bucket_(qps, burst) {}

  void add(const std::string& item) {
    std::lock_guard<std::mutex> g(mu_);
    add_locked(item);
    cv_.notify_one();
  }

  void add_after(const std::string& item, double delay) {
    if (delay <= 0) {
      add(item);
      return;
    }
    std::lock_guard<std::mutex> g(mu_);
    if (shutdown_) return;
    waiting_.push({now_s() + delay, ++seq_, item});
    cv_.notify_one();
  }

  void add_rate_limited(const std::string& item) {
    double d = std::max(item_rl_.when(item), bucket_.when());
    add_after(item, d);
  }

  void forget(const std::string& item) { item_rl_.forget(item); }
  int num_requeues(const std::string& item) { return item_rl_.num_requeues(item); }

  // Returns 1 with *out set, 0 on timeout, -1 on shutdown-and-drained.
  int get(double timeout_s, bool block_forever, std::string* out) {
    std::unique_lock<std::mutex> lk(mu_);
    double deadline = block_forever ? 0.0 : now_s() + timeout_s;
    for (;;) {
      drain_ready_locked();
      if (!queue_.empty()) {
        *out = queue_.front();
        queue_.pop_front();
        dirty_.erase(*out);
        processing_.insert(*out);
        return 1;
      }
      if (shutdown_) return -1;
      double wait = -1.0;  // forever
      if (!waiting_.empty()) wait = std::max(0.0, waiting_.top().ready_at - now_s());
      if (!block_forever) {
        double rem = deadline - now_s();
        if (rem <= 0) return 0;
        wait = (wait < 0) ? rem : std::min(wait, rem);
      }
      if (wait < 0) {
        cv_.wait(lk);
      } else {
        cv_.wait_for(lk, std::chrono::duration<double>(wait));
      }
    }
  }

  void done(const std::string& item) {
    std::lock_guard<std::mutex> g(mu_);
    processing_.erase(item);
    if (dirty_.count(item)) {
      queue_.push_back(item);
      cv_.notify_one();
    }
  }

  void shut_down() {
    std::lock_guard<std::mutex> g(mu_);
    shutdown_ = true;
    cv_.notify_all();
  }

  int size() {
    std::lock_guard<std::mutex> g(mu_);
    return static_cast<int>(queue_.size());
  }

 private:
  struct Waiting {
    double ready_at;
    uint64_t seq;
    std::string item;
    bool operator>(const Waiting& o) const {
      return ready_at != o.ready_at ? ready_at > o.ready_at : seq > o.seq;
    }
  };

  void add_locked(const std::string& item) {
    if (shutdown_ || dirty_.count(item)) return;
    dirty_.insert(item);
    if (!processing_.count(item)) queue_.push_back(item);
  }

  void drain_ready_locked() {
    double now = now_s();
    while (!waiting_.empty() && waiting_.top().ready_at <= now) {
      std::string item = waiting_.top().item;
      waiting_.pop();
      if (!dirty_.count(item)) {
        dirty_.insert(item);
        if (!processing_.count(item)) queue_.push_back(item);
      }
    }
  }

  ItemExponentialRateLimiter item_rl_;
  BucketRateLimiter bucket_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::string> queue_;
  std::unordered_set<std::string> dirty_, processing_;
  std::priority_queue<Waiting, std::vector<Waiting>, std::greater<Waiting>> waiting_;
  uint64_t seq_ = 0;
  bool shutdown_ = false;
};

// ---------------------------------------------------------------------------
// Expectations cache (k8s ControllerExpectations; see core/expectations.py)
// ---------------------------------------------------------------------------

constexpr double kExpectationsTimeoutS = 5 * 60.0;

class Expectations {
 public:
  void expect(const std::string& key, int adds, int dels) {
    std::lock_guard<std::mutex> g(mu_);
    entries_[key] = {adds, dels, now_s()};
  }

  void raise_exp(const std::string& key, int adds, int dels) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = entries_.find(key);
    if (it == entries_.end()) {
      entries_[key] = {adds, dels, now_s()};
    } else {
      it->second.adds += adds;
      it->second.dels += dels;
    }
  }

  void observe(const std::string& key, int adds, int dels) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = entries_.find(key);
    if (it != entries_.end()) {
      it->second.adds -= adds;
      it->second.dels -= dels;
    }
  }

  bool satisfied(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = entries_.find(key);
    if (it == entries_.end()) return true;
    const Entry& e = it->second;
    if (e.adds <= 0 && e.dels <= 0) return true;
    return now_s() - e.ts > kExpectationsTimeoutS;
  }

  void erase(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    entries_.erase(key);
  }

 private:
  struct Entry {
    int adds, dels;
    double ts;
  };
  std::mutex mu_;
  std::unordered_map<std::string, Entry> entries_;
};

// ---------------------------------------------------------------------------
// Process supervisor (kubelet stand-in for the local-process runtime)
// ---------------------------------------------------------------------------

class Supervisor {
 public:
  ~Supervisor() {
    std::lock_guard<std::mutex> g(mu_);
    for (auto& kv : procs_) {
      if (kv.second.pidfd >= 0) close(kv.second.pidfd);
    }
  }

  // Returns pid > 0 on success, -errno on failure.
  long spawn(char* const argv[], char* const envp[], const char* cwd,
             const char* logfile) {
    int err_pipe[2];
    if (pipe2(err_pipe, O_CLOEXEC) != 0) return -errno;

    pid_t pid = fork();
    if (pid < 0) {
      int e = errno;
      close(err_pipe[0]);
      close(err_pipe[1]);
      return -e;
    }
    if (pid == 0) {
      // Child: own session+process group so terminate/kill reach the whole
      // tree; stdio to the log file (or /dev/null); report exec errno up the
      // CLOEXEC pipe so the parent sees spawn failures synchronously.
      close(err_pipe[0]);
      setsid();
      int fd = -1;
      if (logfile && logfile[0]) {
        fd = open(logfile, O_WRONLY | O_CREAT | O_APPEND, 0644);
      }
      if (fd < 0) fd = open("/dev/null", O_WRONLY);
      if (fd >= 0) {
        dup2(fd, 1);
        dup2(fd, 2);
        if (fd > 2) close(fd);
      }
      int devnull = open("/dev/null", O_RDONLY);
      if (devnull >= 0) {
        dup2(devnull, 0);
        if (devnull > 2) close(devnull);
      }
      if (cwd && cwd[0] && chdir(cwd) != 0) {
        int e = errno;
        ssize_t n = write(err_pipe[1], &e, sizeof(e));
        (void)n;
        _exit(127);
      }
      // The child owns a private copy of the address space: installing envp
      // here (not in the parent) keeps concurrent spawns race-free.
      if (envp) environ = const_cast<char**>(envp);
      execvp(argv[0], argv);
      int e = errno;
      ssize_t n = write(err_pipe[1], &e, sizeof(e));
      (void)n;
      _exit(127);
    }

    close(err_pipe[1]);
    int child_errno = 0;
    ssize_t n = read(err_pipe[0], &child_errno, sizeof(child_errno));
    close(err_pipe[0]);
    if (n > 0) {  // exec failed
      int status;
      waitpid(pid, &status, 0);
      return -(child_errno ? child_errno : ECHILD);
    }

    int pidfd = static_cast<int>(syscall(SYS_pidfd_open, pid, 0));
    std::lock_guard<std::mutex> g(mu_);
    procs_[pid] = {pidfd, false, 0};
    return pid;
  }

  // 1 = exited (*code set), 0 = still running, -1 = unknown pid.
  int poll_proc(long pid) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = procs_.find(static_cast<pid_t>(pid));
    if (it == procs_.end()) return -1;
    if (it->second.reaped) return 1;
    return try_reap_locked(it) ? 1 : 0;
  }

  // 1 = exited within timeout (*code set), 0 = timeout, -1 = unknown pid.
  // timeout_s < 0 means block forever.
  int wait_proc(long pid, double timeout_s, int* code) {
    // Poll a dup of the pidfd: a concurrent release() may close the original
    // while we sleep, and the dup keeps the open description alive.
    int pidfd = -1;
    {
      std::lock_guard<std::mutex> g(mu_);
      auto it = procs_.find(static_cast<pid_t>(pid));
      if (it == procs_.end()) return -1;
      if (it->second.reaped) {
        *code = it->second.exit_code;
        return 1;
      }
      if (it->second.pidfd >= 0) pidfd = dup(it->second.pidfd);
    }
    double deadline = timeout_s < 0 ? -1 : now_s() + timeout_s;
    int result;
    for (;;) {
      if (pidfd >= 0) {
        struct pollfd pfd = {pidfd, POLLIN, 0};
        int ms = -1;
        if (deadline >= 0) {
          double rem = deadline - now_s();
          if (rem < 0) rem = 0;
          ms = static_cast<int>(rem * 1000);
        }
        int r = poll(&pfd, 1, ms);
        if (r < 0 && errno != EINTR) {
          result = -1;
          break;
        }
        if (r == 0) {
          result = 0;  // timeout
          break;
        }
      } else {
        // No pidfd (old kernel): poll with sleeps.
        usleep(20000);
      }
      std::lock_guard<std::mutex> g(mu_);
      auto it = procs_.find(static_cast<pid_t>(pid));
      if (it == procs_.end()) {
        result = -1;  // released concurrently
        break;
      }
      if (it->second.reaped || try_reap_locked(it)) {
        *code = it->second.exit_code;
        result = 1;
        break;
      }
      if (deadline >= 0 && now_s() >= deadline) {
        result = 0;
        break;
      }
    }
    if (pidfd >= 0) close(pidfd);
    return result;
  }

  int exit_code(long pid) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = procs_.find(static_cast<pid_t>(pid));
    if (it == procs_.end() || !it->second.reaped) return -1;
    return it->second.exit_code;
  }

  // Signal the whole process group.
  void signal_group(long pid, int sig) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = procs_.find(static_cast<pid_t>(pid));
    if (it == procs_.end() || it->second.reaped) return;
    kill(-static_cast<pid_t>(pid), sig);
  }

  void release(long pid) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = procs_.find(static_cast<pid_t>(pid));
    if (it == procs_.end()) return;
    if (!it->second.reaped) {
      // Last resort: don't leak a zombie; kill and reap synchronously.
      kill(-static_cast<pid_t>(pid), SIGKILL);
      int status;
      waitpid(static_cast<pid_t>(pid), &status, 0);
    }
    if (it->second.pidfd >= 0) close(it->second.pidfd);
    procs_.erase(it);
  }

 private:
  struct Proc {
    int pidfd;
    bool reaped;
    int exit_code;
  };

  bool try_reap_locked(std::unordered_map<pid_t, Proc>::iterator it) {
    int status = 0;
    pid_t r = waitpid(it->first, &status, WNOHANG);
    if (r != it->first) return false;
    int code = 0;
    if (WIFEXITED(status)) {
      code = WEXITSTATUS(status);
    } else if (WIFSIGNALED(status)) {
      code = 128 + WTERMSIG(status);
    }
    it->second.reaped = true;
    it->second.exit_code = code;
    return true;
  }

  std::mutex mu_;
  std::unordered_map<pid_t, Proc> procs_;
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// --- workqueue ---
void* tq_new(double qps, int burst, double base_delay, double max_delay) {
  return new WorkQueue(qps, burst, base_delay, max_delay);
}
void tq_free(void* q) { delete static_cast<WorkQueue*>(q); }
void tq_add(void* q, const char* item) { static_cast<WorkQueue*>(q)->add(item); }
void tq_add_after(void* q, const char* item, double delay) {
  static_cast<WorkQueue*>(q)->add_after(item, delay);
}
void tq_add_rate_limited(void* q, const char* item) {
  static_cast<WorkQueue*>(q)->add_rate_limited(item);
}
void tq_forget(void* q, const char* item) { static_cast<WorkQueue*>(q)->forget(item); }
int tq_num_requeues(void* q, const char* item) {
  return static_cast<WorkQueue*>(q)->num_requeues(item);
}
int tq_get(void* q, double timeout_s, int block_forever, char* buf, int buflen) {
  std::string out;
  int r = static_cast<WorkQueue*>(q)->get(timeout_s, block_forever != 0, &out);
  if (r == 1) {
    size_t n = std::min(out.size(), static_cast<size_t>(buflen - 1));
    memcpy(buf, out.data(), n);
    buf[n] = '\0';
  }
  return r;
}
void tq_done(void* q, const char* item) { static_cast<WorkQueue*>(q)->done(item); }
void tq_shutdown(void* q) { static_cast<WorkQueue*>(q)->shut_down(); }
int tq_len(void* q) { return static_cast<WorkQueue*>(q)->size(); }

// --- expectations ---
void* te_new() { return new Expectations(); }
void te_free(void* e) { delete static_cast<Expectations*>(e); }
void te_expect(void* e, const char* key, int adds, int dels) {
  static_cast<Expectations*>(e)->expect(key, adds, dels);
}
void te_raise(void* e, const char* key, int adds, int dels) {
  static_cast<Expectations*>(e)->raise_exp(key, adds, dels);
}
void te_observe(void* e, const char* key, int adds, int dels) {
  static_cast<Expectations*>(e)->observe(key, adds, dels);
}
int te_satisfied(void* e, const char* key) {
  return static_cast<Expectations*>(e)->satisfied(key) ? 1 : 0;
}
void te_delete(void* e, const char* key) { static_cast<Expectations*>(e)->erase(key); }

// --- exit-code policy (train_util.go:18-55 semantics; see utils/exit_codes.py)
int tx_is_retryable(int code) {
  switch (code) {
    case 130:  // SIGINT
    case 137:  // SIGKILL
    case 138:  // SIGUSR1: user-declared retryable
    case 143:  // SIGTERM
      return 1;
    case 1:
    case 2:
    case 126:
    case 127:
    case 128:
    case 139:  // SIGSEGV
      return 0;
    default:
      return code > 128 ? 1 : 0;
  }
}

// --- supervisor ---
void* ts_new() { return new Supervisor(); }
void ts_free(void* s) { delete static_cast<Supervisor*>(s); }
long ts_spawn(void* s, char* const argv[], char* const envp[], const char* cwd,
              const char* logfile) {
  return static_cast<Supervisor*>(s)->spawn(argv, envp, cwd, logfile);
}
int ts_poll(void* s, long pid) { return static_cast<Supervisor*>(s)->poll_proc(pid); }
int ts_wait(void* s, long pid, double timeout_s, int* code) {
  return static_cast<Supervisor*>(s)->wait_proc(pid, timeout_s, code);
}
int ts_exit_code(void* s, long pid) {
  return static_cast<Supervisor*>(s)->exit_code(pid);
}
void ts_signal(void* s, long pid, int sig) {
  static_cast<Supervisor*>(s)->signal_group(pid, sig);
}
void ts_release(void* s, long pid) { static_cast<Supervisor*>(s)->release(pid); }

const char* tpujob_native_version() { return "1"; }

}  // extern "C"
